"""trace-safety checker: host-sync escapes inside jit-reachable code.

Inside every jit-reachable function (see ``jitgraph.PackageIndex``) the
shared taint pass (``tainting.Taint``) marks values derived from tracer
params; the checker then flags:

* ``trace-host-sync`` — ``float()``/``int()``/``bool()`` over a traced
  value, ``.item()``/``.tolist()``/``.asnumpy()``/
  ``.block_until_ready()``/``jax.device_get``, and ``np.*``/``onp.*``
  calls fed traced arrays: each forces a device->host round-trip (a
  trace-time error or a silent pipeline stall);
* ``trace-tracer-branch`` — Python ``if``/``while``/``assert``/ternary
  over a traced value, or ``for … in range(traced)``: concretization
  errors under jit (the lax.cond/scan/where rewrite is the fix).
  Deliberately NOT flagged: iterating Python containers of tracers
  (``zip``/``enumerate``/list literals — legal trace-time unrolling)
  and bare ``while stack:`` worklists over Python lists;
* ``trace-host-callback`` — ``jax.pure_callback``/``io_callback``/
  ``jax.debug.*`` inside jit-reachable code (this TPU platform does not
  support host callbacks).

Taint is deliberately shape-blind: ``x.shape``/``x.ndim``/``len(x)``
are trace-time Python values, so branching on them is NOT a
trace-safety violation (the retrace checker owns that hazard).
"""
from __future__ import annotations

import ast

from .core import Finding, ModuleInfo
from .jitgraph import (PackageIndex, call_target_name, call_target_parts,
                       shallow_walk)
from .tainting import (NUMPY_ROOTS, SYNC_BUILTINS, SYNC_METHODS,
                       is_iter_adapter)

RULES = {
    "trace-host-sync":
        "device->host sync (float/int/bool/.item()/.asnumpy()/np.*/"
        "block_until_ready) on a traced value inside jit-reachable code",
    "trace-tracer-branch":
        "Python control flow (if/while/assert/range) over a traced "
        "value inside jit-reachable code",
    "trace-host-callback":
        "host callback (jax.pure_callback/io_callback/jax.debug) inside "
        "jit-reachable code",
}

_CALLBACKS = {"pure_callback", "io_callback", "debug_callback",
              "host_callback"}


def _callback_call(parts) -> bool:
    if not parts:
        return False
    if parts[-1] in _CALLBACKS:
        return True
    # jax.debug.print / jax.debug.callback / debug.breakpoint
    if "debug" in parts[:-1] and parts[-1] in ("print", "callback",
                                               "breakpoint"):
        return True
    return False


def _span_text(module: ModuleInfo, node) -> str:
    try:
        return ast.get_source_segment(module.source, node) or ""
    except Exception:
        return ""


def _branch_findings(module, taint, fi, node, findings):
    ctx = fi.qualname
    if isinstance(node, (ast.If, ast.While)) and taint.expr(node.test):
        # bare `while stack:` worklists over Python lists are idiomatic;
        # only comparisons/arithmetic over traced values concretize
        if isinstance(node, ast.While) and \
                isinstance(node.test, (ast.Name, ast.Attribute)):
            return
        findings.append(Finding(
            "trace-tracer-branch", module.relpath, node.lineno,
            node.col_offset,
            "Python %s over a traced value %r concretizes under jit — "
            "use lax.cond/jnp.where" % (
                "while" if isinstance(node, ast.While) else "if",
                _span_text(module, node.test)[:60]), ctx))
    elif isinstance(node, ast.IfExp) and taint.expr(node.test):
        findings.append(Finding(
            "trace-tracer-branch", module.relpath, node.lineno,
            node.col_offset,
            "conditional expression over a traced value %r — use "
            "jnp.where/lax.cond" % (_span_text(module,
                                               node.test)[:60],), ctx))
    elif isinstance(node, ast.For):
        it = node.iter
        if isinstance(it, ast.Call) and \
                call_target_name(it) == "range" and \
                any(taint.expr(a) for a in it.args):
            findings.append(Finding(
                "trace-tracer-branch", module.relpath, node.lineno,
                node.col_offset,
                "for over range(%s) of a traced value — use "
                "lax.fori_loop/scan" % (
                    _span_text(module, it.args[-1])[:50],), ctx))
        elif not is_iter_adapter(it) and not isinstance(
                it, (ast.Name, ast.Attribute)) and taint.expr(it):
            findings.append(Finding(
                "trace-tracer-branch", module.relpath, node.lineno,
                node.col_offset,
                "Python for over a traced value %r unrolls per element "
                "at trace time — use lax.scan/fori_loop"
                % (_span_text(module, it)[:60],), ctx))
    elif isinstance(node, ast.Assert) and taint.expr(node.test):
        findings.append(Finding(
            "trace-tracer-branch", module.relpath, node.lineno,
            node.col_offset,
            "assert over a traced value concretizes under jit — use "
            "checkify or drop the assert", ctx))


def check(module: ModuleInfo, index: PackageIndex):
    findings = []
    for fi in index.functions_in(module):
        if not fi.reachable or isinstance(fi.node, ast.Lambda):
            continue
        taint = index.taint(fi)
        ctx = fi.qualname
        for node in index.shallow_nodes(fi):
            _branch_findings(module, taint, fi, node, findings)
            if not isinstance(node, ast.Call):
                continue
            name = call_target_name(node)
            parts = call_target_parts(node)
            if name in SYNC_BUILTINS and len(node.args) >= 1 and \
                    isinstance(node.func, ast.Name) and \
                    taint.expr(node.args[0]):
                findings.append(Finding(
                    "trace-host-sync", module.relpath, node.lineno,
                    node.col_offset,
                    "%s() over a traced value forces a device->host "
                    "sync under jit" % name, ctx))
            elif name in SYNC_METHODS and \
                    isinstance(node.func, ast.Attribute) and \
                    (taint.expr(node.func.value)
                     or name == "block_until_ready"):
                findings.append(Finding(
                    "trace-host-sync", module.relpath, node.lineno,
                    node.col_offset,
                    ".%s() inside jit-reachable code forces a "
                    "device->host sync" % name, ctx))
            elif name == "device_get":
                findings.append(Finding(
                    "trace-host-sync", module.relpath, node.lineno,
                    node.col_offset,
                    "jax.device_get inside jit-reachable code forces a "
                    "device->host sync", ctx))
            elif parts and parts[0] in NUMPY_ROOTS and (
                    any(taint.expr(a) for a in node.args)
                    or any(taint.expr(k.value) for k in node.keywords)):
                findings.append(Finding(
                    "trace-host-sync", module.relpath, node.lineno,
                    node.col_offset,
                    "%s over a traced value pulls the array to host — "
                    "use the jnp equivalent" % ".".join(parts), ctx))
            elif _callback_call(parts):
                findings.append(Finding(
                    "trace-host-callback", module.relpath, node.lineno,
                    node.col_offset,
                    "%s inside jit-reachable code: host callbacks are "
                    "unsupported on this TPU platform — use a jax-"
                    "native formulation" % ".".join(parts), ctx))
    return findings
