#!/usr/bin/env python
"""Communication-bandwidth probe (reference ``tools/bandwidth/measure.py``).

The reference measures kvstore push/pull GB/s across GPUs to size
gradient aggregation; the TPU-native equivalents are the three links a
training step actually exercises:

  * ``h2d`` / ``d2h`` — host↔device ``device_put`` / ``np.asarray``
    transfer (the input-pipeline link),
  * ``copy`` — on-device HBM copy bandwidth (a donated a+0 roundtrip),
  * ``allreduce`` — jitted ``psum`` over all visible devices (the
    gradient-aggregation link; ICI on real multi-chip, shared memory on
    the virtual CPU mesh).

Sizes sweep powers of two like the reference's ``--num-batches`` sweep.

    python tools/bandwidth.py
    python tools/bandwidth.py --sizes-mb 1,16,64 --format tsv
"""
import argparse
import json
import time

import numpy as onp


def _sync(y):
    """Force completion.  block_until_ready does not actually block on
    the axon tunnel platform — a one-element host readback does."""
    if y is None:
        return
    onp.asarray(y).ravel()[:1] if isinstance(y, onp.ndarray) else \
        onp.asarray(y.ravel()[:1])


def _bench(fn, sync, warmup=2, iters=5):
    for _ in range(warmup):
        sync(fn())
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn()
    sync(out)
    return (time.perf_counter() - t0) / iters


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--sizes-mb", default="1,4,16,64")
    ap.add_argument("--iters", type=int, default=5)
    ap.add_argument("--format", default="json", choices=["json", "tsv"])
    args = ap.parse_args(argv)

    import jax
    import jax.numpy as jnp

    devs = jax.devices()
    dev = devs[0]
    rows = []
    # hoisted out of the size loop (graftlint retrace-jit-in-loop): one
    # callable keeps its per-shape compile cache across iterations
    add0 = jax.jit(lambda a: a + 0.0)
    for mb in [float(s) for s in args.sizes_mb.split(",")]:
        n = int(mb * 1e6 / 4)
        host = onp.random.RandomState(0).rand(n).astype("float32")
        row = {"size_mb": mb, "devices": len(devs)}

        x = jax.device_put(host, dev)
        _sync(x)
        row["h2d_gbs"] = round(mb / 1e3 / _bench(
            lambda: jax.device_put(host, dev), _sync, iters=args.iters), 2)
        row["d2h_gbs"] = round(mb / 1e3 / _bench(
            lambda: onp.asarray(x), lambda y: None, iters=args.iters), 2)

        # read + write: 2x the buffer moves through HBM per call
        row["copy_gbs"] = round(2 * mb / 1e3 / _bench(
            lambda: add0(x), _sync, iters=args.iters), 2)

        if len(devs) > 1:
            from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
            mesh = Mesh(onp.asarray(devs), ("dp",))
            sharded = jax.device_put(
                onp.tile(host[None], (len(devs), 1)),
                NamedSharding(mesh, P("dp", None)))

            from mxnet_tpu.parallel.mesh import shard_map_compat

            @jax.jit
            def ar(v):
                return shard_map_compat(
                    lambda s: jax.lax.psum(s, "dp"), mesh=mesh,
                    in_specs=P("dp", None), out_specs=P(None, None))(v)
            # algorithmic bytes: each device contributes its shard once
            row["allreduce_gbs"] = round(
                mb * len(devs) / 1e3 / _bench(
                    lambda: ar(sharded), _sync, iters=args.iters), 2)
        rows.append(row)

    if args.format == "tsv":
        keys = list(rows[0])
        print("\t".join(keys))
        for r in rows:
            print("\t".join(str(r.get(k, "")) for k in keys))
    else:
        for r in rows:
            print(json.dumps(r))
    return rows


if __name__ == "__main__":
    main()
